"""End-to-end server tests over a real WebSocket, with a fake encoder
(no TPU/jit) standing in for the tpuenc pipeline."""

import asyncio
import json
import os

import numpy as np
import pytest
import websockets

from selkies_tpu.encoder.jpeg import StripeOutput
from selkies_tpu.protocol import unpack_binary, VideoStripe
from selkies_tpu.server.app import StreamingApp
from selkies_tpu.server.data_server import DataStreamingServer
from selkies_tpu.settings import Settings


class FakeEncoder:
    """Pipelined-encoder lookalike: every submitted frame yields one stripe."""

    def __init__(self):
        self.submitted = 0
        self._ready = []

    def submit(self, frame):
        self.submitted += 1
        self._ready.append(
            (self.submitted,
             [StripeOutput(y_start=0, height=64,
                           jpeg=b"\xff\xd8FAKE%d" % self.submitted + b"\xff\xd9",
                           is_paintover=False)]))

    def poll(self):
        out, self._ready = self._ready, []
        return out

    def flush(self):
        return self.poll()


class FakeSource:
    def __init__(self, width, height, fps):
        self.width, self.height, self.fps = width, height, fps

    def start(self):
        pass

    def stop(self):
        pass

    def next_frame(self):
        return np.zeros((self.height, self.width, 3), np.uint8)


@pytest.fixture
def anyio_backend():
    return "asyncio"


def make_server(tmp_path, **settings_env):
    env = {"SELKIES_PORT": "0"}
    env.update(settings_env)
    settings = Settings(argv=[], env=env)
    app = StreamingApp(settings)
    encoders = []

    def encoder_factory(w, h, s):
        enc = FakeEncoder()
        encoders.append(enc)
        return enc

    server = DataStreamingServer(
        settings, app=app,
        encoder_factory=encoder_factory,
        source_factory=lambda w, h, fps: FakeSource(w, h, fps),
        host="127.0.0.1",
    )
    app.data_server = server
    os.environ["SELKIES_UPLOAD_DIR"] = str(tmp_path / "uploads")
    return server, app, encoders


async def start_on_free_port(server):
    import websockets.asyncio.server as ws_server

    server._stop_event = asyncio.Event()
    srv = await ws_server.serve(
        server.ws_handler, "127.0.0.1", 0, compression=None, max_size=None)
    server._server = srv
    port = srv.sockets[0].getsockname()[1]
    return srv, port


async def handshake(ws):
    assert await ws.recv() == "MODE websockets"
    schema = json.loads(await ws.recv())
    assert schema["type"] == "server_settings"
    return schema


@pytest.mark.anyio
async def test_handshake_and_video_flow(tmp_path):
    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            schema = await handshake(ws)
            assert "encoder" in schema["settings"]

            await ws.send('SETTINGS,' + json.dumps({
                "displayId": "primary",
                "initialClientWidth": 320,
                "initialClientHeight": 240,
                "framerate": 30,
            }))
            # PIPELINE_RESETTING broadcast then binary stripes (stats JSON
            # may interleave)
            while True:
                reset = await asyncio.wait_for(ws.recv(), 5)
                if reset == "PIPELINE_RESETTING primary":
                    break
            while True:
                frame = await asyncio.wait_for(ws.recv(), 5)
                if isinstance(frame, bytes):
                    break
            f = unpack_binary(frame)
            assert isinstance(f, VideoStripe)
            assert f.payload.startswith(b"\xff\xd8FAKE")
            assert f.frame_id == 1

            # ACK flows into backpressure state
            await ws.send(f"CLIENT_FRAME_ACK {f.frame_id}")
            await asyncio.sleep(0.1)
            st = server.display_clients["primary"]
            assert st.bp.acknowledged_frame_id == f.frame_id
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_stop_start_video(tmp_path):
    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send('SETTINGS,{"displayId": "primary"}')
            await asyncio.wait_for(ws.recv(), 5)  # PIPELINE_RESETTING

            await ws.send("STOP_VIDEO")
            # drain until VIDEO_STOPPED
            while True:
                m = await asyncio.wait_for(ws.recv(), 5)
                if m == "VIDEO_STOPPED":
                    break
            st = server.display_clients["primary"]
            assert st.capture_task is None

            await ws.send("START_VIDEO")
            while True:
                m = await asyncio.wait_for(ws.recv(), 5)
                if m == "VIDEO_STARTED":
                    break
            assert st.capture_task is not None
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_second_screen_disabled_kills_client(tmp_path):
    server, app, encoders = make_server(
        tmp_path, SELKIES_SECOND_SCREEN="false")
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send('SETTINGS,{"displayId": "display2"}')
            while True:
                msg = await asyncio.wait_for(ws.recv(), 5)
                if isinstance(msg, str) and msg.startswith("KILL"):
                    break
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_file_upload_and_path_traversal(tmp_path):
    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send("FILE_UPLOAD_START:sub/ok.txt:11")
            await ws.send(b"\x01hello")
            await ws.send(b"\x01 world")
            await ws.send("FILE_UPLOAD_END:sub/ok.txt")
            await asyncio.sleep(0.2)
            target = tmp_path / "uploads" / "sub" / "ok.txt"
            assert target.read_bytes() == b"hello world"

            await ws.send("FILE_UPLOAD_START:../evil.txt:4")
            msg = await asyncio.wait_for(ws.recv(), 5)
            assert msg.startswith("FILE_UPLOAD_ERROR")
            assert not (tmp_path / "evil.txt").exists()
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_resize_broadcasts_resolution(tmp_path):
    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send('SETTINGS,{"displayId": "primary"}')
            await asyncio.wait_for(ws.recv(), 5)
            await ws.send("r,1280x720,primary")
            while True:
                m = await asyncio.wait_for(ws.recv(), 5)
                if isinstance(m, str) and m.startswith("{"):
                    d = json.loads(m)
                    if d.get("type") == "stream_resolution":
                        assert (d["width"], d["height"]) == (1280, 720)
                        break
            assert server.display_clients["primary"].width == 1280
    finally:
        await server.stop()
        srv.close()


def test_backpressure_state_logic():
    from selkies_tpu.server.backpressure import BackpressureState

    bp = BackpressureState(framerate=60)
    t = 1000.0
    bp.reset(now=t)
    # healthy: acked close behind sent
    for i in range(1, 100):
        bp.on_frame_sent(i, now=t + i * 0.016)
    bp.on_client_ack(95, now=t + 99 * 0.016)
    assert bp.evaluate(now=t + 99 * 0.016) is True

    # desync beyond 2s of frames → gate closes
    bp2 = BackpressureState(framerate=60)
    bp2.reset(now=t)
    for i in range(1, 300):
        bp2.on_frame_sent(i, now=t + i * 0.016)
    bp2.on_client_ack(10, now=t + 1.0)
    assert bp2.evaluate(now=t + 5.0) is False  # 289 frames > 120 allowed

    # stall: no ACK for > 4s
    bp3 = BackpressureState(framerate=60)
    bp3.reset(now=t)
    bp3.on_frame_sent(1, now=t)
    bp3.on_client_ack(1, now=t)
    assert bp3.evaluate(now=t + 0.1) is True
    assert bp3.evaluate(now=t + 4.5) is False

    # legitimate wrap: sender wrapped past 65535, client still far behind —
    # modular desync sees the true 5539-frame gap and keeps the gate closed
    # (the reference's abs() heuristic would wrongly treat this as an anomaly)
    bp4 = BackpressureState(framerate=60)
    bp4.reset(now=t)
    bp4.on_frame_sent(3, now=t)
    bp4.on_client_ack(60000, now=t)
    assert bp4.evaluate(now=t + 1) is False

    # true anomaly: client ACKs an id "ahead" of the sender → reset posture
    bp5 = BackpressureState(framerate=60)
    bp5.reset(now=t)
    bp5.on_frame_sent(5, now=t)
    bp5.on_client_ack(10, now=t)
    assert bp5.evaluate(now=t + 1) is True


@pytest.mark.anyio
async def test_settings_overrides_reach_encoder_factory(tmp_path):
    settings = Settings(argv=[], env={})
    seen = {}

    def factory(w, h, s, overrides=None):
        seen.update(overrides or {})
        return FakeEncoder()

    server = DataStreamingServer(
        settings, app=None, encoder_factory=factory,
        source_factory=lambda w, h, fps: FakeSource(w, h, fps),
        host="127.0.0.1")
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send('SETTINGS,' + json.dumps(
                {"displayId": "primary", "jpeg_quality": 77,
                 "framerate": 24}))
            await asyncio.sleep(0.3)
            assert seen.get("jpeg_quality") == 77
            st = server.display_clients["primary"]
            assert st.bp.framerate == 24.0
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_upload_exceeding_declared_size_rejected(tmp_path):
    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send("FILE_UPLOAD_START:big.bin:4")
            await ws.send(b"\x01" + b"x" * 100)
            msg = await asyncio.wait_for(ws.recv(), 5)
            assert msg.startswith("FILE_UPLOAD_ERROR")
            assert not (tmp_path / "uploads" / "big.bin").exists()
            # further chunks are ignored, session stays alive
            await ws.send(b"\x01more")
            await ws.send("r,bogus")  # malformed resize is tolerated too
            await ws.send("CLIENT_FRAME_ACK notanint")
            pong = await ws.ping()
            await asyncio.wait_for(pong, 5)  # socket still open, not torn down
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_resize_resets_frame_ids(tmp_path):
    """A capture restart renumbers frames from 1, so the server must emit
    PIPELINE_RESETTING (else the backpressure gate wedges on stale ACKs)."""
    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send('SETTINGS,{"displayId": "primary"}')
            await asyncio.wait_for(ws.recv(), 5)
            st = server.display_clients["primary"]
            st.bp.on_frame_sent(40000)
            st.bp.on_client_ack(40000)
            await ws.send("r,1280x720,primary")
            saw_reset = False
            for _ in range(20):
                m = await asyncio.wait_for(ws.recv(), 5)
                if isinstance(m, str) and m.startswith("PIPELINE_RESETTING"):
                    saw_reset = True
                    break
            assert saw_reset
            # restarted loop renumbers from 1 — the stale 40000 horizon is gone
            assert st.bp.last_sent_frame_id < 100
            assert st.bp.send_enabled
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_reconnect_resyncs_frame_ids_and_keyframe(tmp_path):
    """Satellite (ISSUE 2): client disconnect mid-stream then reconnect
    exercises _reset_frame_ids_and_notify — frame IDs restart at 1, the
    rebuilt encoder leads with a keyframe, and the reset precedes media."""
    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send('SETTINGS,' + json.dumps({
                "displayId": "primary", "initialClientWidth": 320,
                "initialClientHeight": 240, "framerate": 30}))
            seen = 0
            while seen < 3:
                m = await asyncio.wait_for(ws.recv(), 5)
                if isinstance(m, bytes):
                    seen += 1
        # socket closed: the handler tears the display down
        for _ in range(100):
            if "primary" not in server.display_clients:
                break
            await asyncio.sleep(0.02)
        assert "primary" not in server.display_clients
        n_enc = len(encoders)

        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws2:
            await handshake(ws2)
            await ws2.send('SETTINGS,' + json.dumps({
                "displayId": "primary", "initialClientWidth": 320,
                "initialClientHeight": 240, "framerate": 30}))
            saw_reset = False
            frame = None
            while frame is None:
                m = await asyncio.wait_for(ws2.recv(), 5)
                if isinstance(m, str) and m.startswith("PIPELINE_RESETTING"):
                    saw_reset = True
                elif isinstance(m, bytes):
                    frame = m
            assert saw_reset, "media arrived before PIPELINE_RESETTING"
            f = unpack_binary(frame)
            assert isinstance(f, VideoStripe)
            assert f.frame_id == 1
            assert f.is_key
            assert len(encoders) > n_enc       # rebuilt, not reused
            st = server.display_clients["primary"]
            assert st.bp.last_sent_frame_id < 100
            assert st.bp.send_enabled
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_multi_display_layout_drives_xrandr(tmp_path, monkeypatch):
    """Two displays attach → the server computes the extended layout, sets
    capture offsets, and (with xrandr 'available') issues the monitor
    grammar; secondary disconnect reflows back to a single display."""
    import selkies_tpu.display as disp_pkg
    import selkies_tpu.display.xrandr as xr_mod

    calls = []

    class FakeXrandr:
        def __init__(self, *a, **k):
            pass

        def resize(self, w, h, refresh=60.0, output=None):
            calls.append(("resize", w, h))
            return f"{w}x{h}"

        def apply_layout(self, layout, refresh=60.0):
            calls.append(("layout", layout.fb_width, layout.fb_height,
                          tuple((p.display_id, p.x, p.y)
                                for p in layout.placements)))

    monkeypatch.setattr(disp_pkg, "xrandr_available", lambda: True)
    monkeypatch.setattr(disp_pkg, "XrandrManager", FakeXrandr)

    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}/") as ws1:
            await handshake(ws1)
            await ws1.send("SETTINGS," + json.dumps(
                {"displayId": "primary", "initialClientWidth": 1920,
                 "initialClientHeight": 1080}))
            await asyncio.sleep(0.3)
            assert ("resize", 1920, 1080) in calls

            async with websockets.connect(f"ws://127.0.0.1:{port}/") as ws2:
                await handshake(ws2)
                await ws2.send("SETTINGS," + json.dumps(
                    {"displayId": "display2", "initialClientWidth": 1280,
                     "initialClientHeight": 720}))
                await asyncio.sleep(0.3)
                layouts = [c for c in calls if c[0] == "layout"]
                assert layouts, calls
                _, fbw, fbh, placements = layouts[-1]
                assert (fbw, fbh) == (3200, 1080)
                assert ("display2", 1920, 0) in placements
                # capture offsets landed on the display state
                st2 = server.display_clients["display2"]
                assert (st2.x, st2.y) == (1920, 0)

            # secondary gone → reflow to single display
            await asyncio.sleep(0.4)
            assert ("resize", 1920, 1080) in calls[-2:] or \
                ("resize", 1920, 1080) in calls
            assert "display2" not in server.display_clients
    finally:
        srv.close()
        await srv.wait_closed()
        await server.stop()


@pytest.mark.anyio
async def test_layout_dedup_skips_repeat_xrandr(tmp_path, monkeypatch):
    import selkies_tpu.display as disp_pkg

    calls = []

    class FakeXrandr:
        def __init__(self, *a, **k):
            pass

        def resize(self, w, h, refresh=60.0, output=None):
            calls.append((w, h))
            return f"{w}x{h}"

        def apply_layout(self, layout, refresh=60.0):
            calls.append(("multi",))

    monkeypatch.setattr(disp_pkg, "xrandr_available", lambda: True)
    monkeypatch.setattr(disp_pkg, "XrandrManager", FakeXrandr)

    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}/") as ws:
            await handshake(ws)
            await ws.send("SETTINGS," + json.dumps(
                {"displayId": "primary", "initialClientWidth": 1024,
                 "initialClientHeight": 768}))
            await asyncio.sleep(0.3)
            n_after_settings = len(calls)
            # same-geometry settings again → no new xrandr traffic
            await ws.send("SETTINGS," + json.dumps(
                {"displayId": "primary", "initialClientWidth": 1024,
                 "initialClientHeight": 768}))
            await asyncio.sleep(0.3)
            assert len(calls) == n_after_settings
            # a real resize does reach xrandr
            await ws.send("r,800x600")
            await asyncio.sleep(0.3)
            assert calls[-1] == (800, 600)
    finally:
        srv.close()
        await srv.wait_closed()
        await server.stop()


@pytest.mark.anyio
async def test_h264_encoder_selection(tmp_path):
    """Client requesting x264enc-striped gets 0x04 frames; x264enc (full
    frame) gets 0x00 — through the real TPU-profile H.264 encoder on CPU."""
    env = {"SELKIES_PORT": "0"}
    settings = Settings(argv=[], env=env)
    app = StreamingApp(settings)
    server = DataStreamingServer(
        settings, app=app,
        source_factory=lambda w, h, fps, **kw: FakeSource(w, h, fps),
        host="127.0.0.1",
    )
    app.data_server = server
    srv, port = await start_on_free_port(server)
    try:
        for encoder, expect_type in (("x264enc-striped", 0x04),
                                     ("x264enc", 0x00)):
            async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
                await handshake(ws)
                await ws.send("SETTINGS," + json.dumps({
                    "initialClientWidth": 64, "initialClientHeight": 64,
                    "encoder": encoder, "framerate": 20}))
                got = None
                for _ in range(300):
                    msg = await asyncio.wait_for(ws.recv(), 10)
                    if isinstance(msg, bytes) and msg and \
                            msg[0] == expect_type:
                        got = msg
                        break
                assert got is not None, f"no 0x{expect_type:02x} frames"
                if expect_type == 0x04:
                    from selkies_tpu.protocol import unpack_binary
                    f = unpack_binary(got)
                    assert f.payload.startswith(b"\x00\x00\x00\x01")
                    assert f.width and f.height
    finally:
        srv.close()
        await server.stop()


@pytest.mark.anyio
async def test_viewer_join_forces_keyframe(tmp_path):
    """A second (sharing) client connecting must kick a full refresh on the
    primary stream — damage gating would otherwise leave it black."""
    server, app, encoders = make_server(tmp_path)
    srv, port = await start_on_free_port(server)
    kicked = []
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as host_ws:
            await handshake(host_ws)
            await host_ws.send("SETTINGS," + json.dumps({"framerate": 30}))
            for _ in range(100):
                if encoders:
                    break
                await asyncio.sleep(0.02)
            assert encoders
            encoders[0].force_keyframe = lambda: kicked.append(True)
            async with websockets.connect(
                    f"ws://127.0.0.1:{port}") as viewer_ws:
                await handshake(viewer_ws)   # viewer never sends SETTINGS
                await asyncio.sleep(0.1)
            assert kicked, "viewer join did not force a keyframe"
    finally:
        srv.close()
        await server.stop()


@pytest.mark.anyio
async def test_mesh_batched_sessions_serve_wire_stripes(tmp_path):
    """BASELINE config 5 as a product path: with tpu_mesh configured, two
    displays' capture loops feed ONE sharded mesh dispatch (CPU mesh here)
    and both websockets receive wire-ready 0x03 JPEG stripes."""
    import io
    from PIL import Image

    server, app, encoders = make_server(
        tmp_path,
        SELKIES_TPU_MESH="session:2,stripe:2",
        SELKIES_TPU_SESSIONS_PER_CHIP="1",
    )
    srv, port = await start_on_free_port(server)

    async def collect_stripes(ws, want):
        got = []
        while len(got) < want:
            m = await asyncio.wait_for(ws.recv(), 30)
            if isinstance(m, bytes):
                f = unpack_binary(m)
                if isinstance(f, VideoStripe):
                    got.append(f)
        return got

    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws1, \
                websockets.connect(f"ws://127.0.0.1:{port}") as ws2:
            await handshake(ws1)
            await handshake(ws2)
            await ws1.send('SETTINGS,' + json.dumps({
                "displayId": "primary",
                "initialClientWidth": 320, "initialClientHeight": 240}))
            await ws2.send('SETTINGS,' + json.dumps({
                "displayId": "display2",
                "initialClientWidth": 320, "initialClientHeight": 240}))

            # primary fans out to all clients; display2 only to its owner —
            # ws2 must see both streams' stripes, ws1 the primary's
            s1 = await collect_stripes(ws1, 2)
            s2 = await collect_stripes(ws2, 2)

            # both displays ride the mesh coordinator, not solo encoders
            assert server.mesh_coordinator is not None
            assert len(server.mesh_coordinator._attached) == 2
            assert encoders == []   # solo factory never invoked

        for f in s1 + s2:
            assert f.payload.startswith(b"\xff\xd8")
            assert f.payload.endswith(b"\xff\xd9")
            img = Image.open(io.BytesIO(f.payload))
            assert img.size[0] == 320
    finally:
        await server.stop()
        srv.close()
        assert server.mesh_coordinator is None or \
            not server.mesh_coordinator._thread


@pytest.mark.anyio
async def test_mesh_stripe_axis_single_session_config4(tmp_path):
    """BASELINE config 4 as a product path: ONE display whose stripes
    shard across the mesh's "stripe" axis (single-session shape, no
    session batching) — the 4K-on-v5e-4 layout, scaled down to the CPU
    test mesh. The display must ride the mesh coordinator, and the wire
    stripes must decode."""
    import io
    from PIL import Image

    server, app, encoders = make_server(
        tmp_path,
        SELKIES_TPU_MESH="stripe:4",
        SELKIES_TPU_SESSIONS_PER_CHIP="1",
    )
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send('SETTINGS,' + json.dumps({
                "displayId": "primary",
                # 512 rows = 8 stripes of 64: divisible across stripe:4
                "initialClientWidth": 320, "initialClientHeight": 512}))
            got = []
            while len(got) < 3:
                m = await asyncio.wait_for(ws.recv(), 30)
                if isinstance(m, bytes):
                    f = unpack_binary(m)
                    if isinstance(f, VideoStripe):
                        got.append(f)
            assert server.mesh_coordinator is not None
            assert server.mesh_coordinator.n_sessions == 1
            assert len(server.mesh_coordinator._attached) == 1
            assert encoders == []      # solo factory never invoked
        for f in got:
            assert f.payload.startswith(b"\xff\xd8")
            img = Image.open(io.BytesIO(f.payload))
            assert img.size[0] == 320
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_mesh_geometry_buckets(tmp_path):
    """A join at a different resolution gets its own mesh bucket instead
    of silently falling back to a solo encoder (VERDICT r2 item 6); the
    fallback/bucket counters ride the stats feed."""
    server, app, encoders = make_server(
        tmp_path,
        SELKIES_TPU_MESH="session:2",
        SELKIES_TPU_SESSIONS_PER_CHIP="1",
    )
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws1, \
                websockets.connect(f"ws://127.0.0.1:{port}") as ws2:
            await handshake(ws1)
            await handshake(ws2)
            await ws1.send('SETTINGS,' + json.dumps({
                "displayId": "primary",
                "initialClientWidth": 320, "initialClientHeight": 240}))
            await ws2.send('SETTINGS,' + json.dumps({
                "displayId": "display2",
                "initialClientWidth": 256, "initialClientHeight": 128}))

            async def first_stripe(ws):
                while True:
                    m = await asyncio.wait_for(ws.recv(), 30)
                    if isinstance(m, bytes):
                        f = unpack_binary(m)
                        if isinstance(f, VideoStripe):
                            return f
            await first_stripe(ws1)
            await first_stripe(ws2)
            assert len(server.mesh_coordinators) == 2   # two buckets
            assert server.mesh_stats["bucketed"] == 2
            assert server.mesh_stats["solo_fallback"] == 0
            assert encoders == []                        # no solo encoder
    finally:
        await server.stop()
        srv.close()


@pytest.mark.anyio
async def test_mesh_h264_display_serves_wire_stripes(tmp_path):
    """VERDICT r3 item 3: an H.264 display rides the tpu_mesh coordinator
    — the wire carries 0x04 striped Annex-B that the conformance oracle
    decodes, with no solo-encoder fallback."""
    from selkies_tpu.encoder import conformance

    server, app, encoders = make_server(
        tmp_path,
        SELKIES_TPU_MESH="session:2,stripe:2",
        SELKIES_TPU_SESSIONS_PER_CHIP="1",
        SELKIES_ENCODER="x264enc-striped",
    )
    srv, port = await start_on_free_port(server)
    try:
        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await handshake(ws)
            await ws.send('SETTINGS,' + json.dumps({
                "displayId": "primary",
                "initialClientWidth": 320, "initialClientHeight": 256}))
            got = []
            while len(got) < 4:
                m = await asyncio.wait_for(ws.recv(), 60)
                if isinstance(m, bytes):
                    f = unpack_binary(m)
                    if isinstance(f, VideoStripe):
                        got.append((m[0], f))
            assert server.mesh_coordinator is not None
            assert server.mesh_coordinator.profile == "x264enc-striped"
            assert len(server.mesh_coordinator._attached) == 1
            assert encoders == []          # solo factory never invoked
    finally:
        await server.stop()
        srv.close()

    for prefix_byte, f in got:
        assert prefix_byte == 0x04        # striped H.264, not JPEG
        assert f.payload.startswith(b"\x00\x00\x00\x01")
    # first stripe sequence decodes in the libavcodec oracle
    if conformance.ConformanceDecoder is not None:
        try:
            dec = conformance.ConformanceDecoder("h264", max_dim=512)
        except RuntimeError:
            return
        y0 = got[0][1].y_start
        n_dec = 0
        for _, f in got:
            if f.y_start != y0:
                continue
            out = dec.decode(f.payload)
            if out is not None:
                n_dec += 1
        n_dec += len(dec.flush())
        dec.close()
        assert n_dec >= 1
