import jax.numpy as jnp
import numpy as np

from selkies_tpu.ops import (
    base_quant_tables,
    block_dct2,
    block_idct2,
    blockify,
    dct8_matrix,
    quality_scaled_tables,
    rgb_to_ycbcr,
    subsample_420,
    unblockify,
)
from selkies_tpu.ops.quant import ZIGZAG, quantize_blocks, zigzag_blocks


def test_dct_matrix_orthonormal():
    c = np.asarray(dct8_matrix())
    np.testing.assert_allclose(c @ c.T, np.eye(8), atol=1e-6)


def test_dct_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.uniform(-128, 127, size=(4, 5, 8, 8)).astype(np.float32)
    y = block_idct2(block_dct2(jnp.asarray(x)))
    np.testing.assert_allclose(np.asarray(y), x, atol=1e-3)


def test_dct_dc_term():
    x = jnp.full((1, 8, 8), 100.0)
    c = np.asarray(block_dct2(x))[0]
    assert abs(c[0, 0] - 800.0) < 1e-3  # orthonormal: DC = 8 * mean
    assert np.abs(c).sum() - abs(c[0, 0]) < 1e-3


def test_blockify_roundtrip():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 255, size=(64, 128)).astype(np.float32)
    b = blockify(jnp.asarray(x))
    assert b.shape == (8, 16, 8, 8)
    np.testing.assert_array_equal(np.asarray(unblockify(b)), x)
    # block (0,1) is columns 8..16 of rows 0..8
    np.testing.assert_array_equal(np.asarray(b[0, 1]), x[:8, 8:16])


def test_rgb_to_ycbcr_known_values():
    rgb = jnp.asarray(
        np.array([[[255, 255, 255], [0, 0, 0], [255, 0, 0]]], dtype=np.uint8)[None]
    )
    y, cb, cr = rgb_to_ycbcr(rgb[0])
    y, cb, cr = np.asarray(y), np.asarray(cb), np.asarray(cr)
    assert abs(y[0, 0] - 255.0) < 0.1 and abs(cb[0, 0] - 128) < 0.6
    assert abs(y[0, 1] - 0.0) < 0.1
    assert abs(y[0, 2] - 76.2) < 0.5 and cr[0, 2] > 200


def test_subsample_420():
    x = jnp.asarray(np.arange(16, dtype=np.float32).reshape(4, 4))
    s = np.asarray(subsample_420(x))
    assert s.shape == (2, 2)
    assert s[0, 0] == (0 + 1 + 4 + 5) / 4


def test_quality_tables_monotone():
    q10_l, _ = quality_scaled_tables(10)
    q90_l, _ = quality_scaled_tables(90)
    assert (q10_l.astype(int) >= q90_l.astype(int)).all()
    q100_l, q100_c = quality_scaled_tables(100)
    assert (q100_l == 1).all() and (q100_c == 1).all()
    q50_l, _ = quality_scaled_tables(50)
    base_l, _ = base_quant_tables()
    np.testing.assert_array_equal(q50_l, base_l)


def test_zigzag_is_permutation():
    assert sorted(ZIGZAG.tolist()) == list(range(64))
    # spec spot checks
    assert ZIGZAG[0] == 0 and ZIGZAG[1] == 1 and ZIGZAG[2] == 8 and ZIGZAG[63] == 63


def test_quantize_and_zigzag():
    coeffs = jnp.asarray(np.full((2, 2, 8, 8), 50.0, dtype=np.float32))
    table = jnp.asarray(np.full((8, 8), 25.0, dtype=np.float32))
    q = quantize_blocks(coeffs, table)
    assert q.dtype == jnp.int16
    assert (np.asarray(q) == 2).all()
    z = zigzag_blocks(q)
    assert z.shape == (2, 2, 64)


def test_full_search_mc_matches_separate_path():
    """The fused ME+MC scan must reproduce full_search_mv + mc_luma +
    mc_chroma exactly (mv tie-breaks included)."""
    import numpy as np
    import jax.numpy as jnp
    from selkies_tpu.ops.motion import (full_search_mc, full_search_mv,
                                        mc_chroma, mc_luma)

    rng = np.random.default_rng(11)
    h, w = 64, 96
    ref = rng.integers(0, 256, (h, w), dtype=np.uint8)
    # shifted + noisy current frame exercises real motion
    cur = np.roll(ref, (3, -5), axis=(0, 1))
    cur = np.clip(cur.astype(np.int32)
                  + rng.integers(-6, 7, cur.shape), 0, 255).astype(np.uint8)
    ref_cb = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
    ref_cr = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)

    mv_want, _, _ = full_search_mv(jnp.asarray(cur), jnp.asarray(ref),
                                   search=8)
    py_want = mc_luma(jnp.asarray(ref), mv_want, search=8)
    pcb_want = mc_chroma(jnp.asarray(ref_cb), mv_want, search=8)
    pcr_want = mc_chroma(jnp.asarray(ref_cr), mv_want, search=8)

    mv, py, pcb, pcr = full_search_mc(
        jnp.asarray(cur), jnp.asarray(ref), jnp.asarray(ref_cb),
        jnp.asarray(ref_cr), search=8)
    np.testing.assert_array_equal(np.asarray(mv), np.asarray(mv_want))
    np.testing.assert_array_equal(np.asarray(py), np.asarray(py_want))
    np.testing.assert_array_equal(np.asarray(pcb), np.asarray(pcb_want))
    np.testing.assert_array_equal(np.asarray(pcr), np.asarray(pcr_want))
