"""On-device CAVLC (encoder/device_cavlc.py): bit-exactness vs native.

Tier-1-safe seeded subset of ``tools/cavlc_fuzz.py --device``: the device
packer's P-slice payloads, glued to a host slice header, must be
BIT-IDENTICAL to native/cavlc.cpp over the full residual surface (luma +
chroma DC/AC, skip/mvd paths, |level| > 127), and overflow must be
flagged exactly where the flat16 + host fallback has to engage.
"""

import numpy as np
import pytest

from selkies_tpu.native import cavlc_lib

pytestmark = pytest.mark.skipif(
    cavlc_lib() is None, reason="native CAVLC reference unavailable")


def _fuzz():
    import importlib
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    return importlib.import_module("cavlc_fuzz")


# one fixed small geometry so the jitted pack compiles once for the whole
# seeded sweep (distinct geometries cost a CPU recompile each)
GEOM = dict(mb_w=4, mb_h=2, S=2)


@pytest.mark.parametrize("seed", range(12))
def test_device_pack_matches_native(seed):
    fuzz = _fuzz()
    ok, why, _ = fuzz.check_device_seed(seed, **GEOM)
    assert ok, why


def test_device_pack_overflow_levels_flagged_and_rest_exact():
    """|level| past the 28-bit escape must flag its stripe (the product
    then recodes it from flat16); clean stripes in the same frame stay
    bit-exact."""
    import jax.numpy as jnp

    from selkies_tpu.encoder import device_cavlc as dcav
    from selkies_tpu.encoder.h264 import encode_picture_nals_np

    mb_w, mb_h, S = 4, 2, 2
    n = mb_w * mb_h
    mv = np.zeros((S, n, 2), np.int32)
    luma = np.zeros((S, n, 16, 4, 4), np.int32)
    cdc = np.zeros((S, n, 2, 2, 2), np.int32)
    cac = np.zeros((S, n, 2, 4, 4, 4), np.int32)
    luma[0, 0, 0, 0, 1] = 3000          # escape overflow → fallback
    luma[1, 2, 3, 2, 2] = 2063          # still encodable, > int8 range
    words, t_bits, base_words, ovf = [np.asarray(x) for x in (
        dcav.pack_p_frame_words(
            jnp.asarray(mv), jnp.asarray(luma), jnp.asarray(cdc),
            jnp.asarray(cac), jnp.ones(S, bool),
            mb_w=mb_w, mb_h=mb_h, max_stripe_bytes=16384))]
    assert list(ovf) == [True, False]
    payload = np.stack(
        [(words >> 24) & 0xFF, (words >> 16) & 0xFF,
         (words >> 8) & 0xFF, words & 0xFF], -1).astype(np.uint8).reshape(-1)
    start = int(base_words[1]) * 4
    nbits = int(t_bits[1])
    got = dcav.assemble_p_slice(
        payload[start:start + ((nbits + 31) // 32) * 4], nbits, 26, 3)
    ldc = np.zeros((n, 4, 4), np.int32)
    ref = encode_picture_nals_np(
        mv[1], luma[1], ldc, cdc[1], cac[1], is_idr=False,
        mb_w=mb_w, mb_h=mb_h, qp=26, frame_num=3)
    assert got == ref


def test_update_mask_packs_nothing():
    """Stripes outside the update mask must contribute zero payload (the
    fetch prefix only carries emitting stripes)."""
    import jax.numpy as jnp

    from selkies_tpu.encoder import device_cavlc as dcav

    mb_w, mb_h, S = 4, 2, 2
    n = mb_w * mb_h
    mv = np.zeros((S, n, 2), np.int32)
    luma = np.zeros((S, n, 16, 4, 4), np.int32)
    luma[:, :, :, 1, 1] = 5
    cdc = np.zeros((S, n, 2, 2, 2), np.int32)
    cac = np.zeros((S, n, 2, 4, 4, 4), np.int32)
    _, t_bits, _, _ = dcav.pack_p_frame_words(
        jnp.asarray(mv), jnp.asarray(luma), jnp.asarray(cdc),
        jnp.asarray(cac), jnp.asarray([True, False]),
        mb_w=mb_w, mb_h=mb_h, max_stripe_bytes=16384)
    t_bits = np.asarray(t_bits)
    assert t_bits[0] > 0 and t_bits[1] == 0


def test_ep_escape_sequential_reset_semantics():
    """00 00 00 00 01 must escape to 00 00 03 00 00 03 01 (the inserted
    0x03 resets the zero-run count) — the exact semantics of
    native/cavlc.cpp append_nal."""
    from selkies_tpu.encoder.device_cavlc import _ep_escape

    assert _ep_escape(np.array([0, 0, 0, 0, 1], np.uint8)) == \
        bytes([0, 0, 3, 0, 0, 3, 1])
    assert _ep_escape(np.array([0, 0, 0, 0, 0, 1], np.uint8)) == \
        bytes([0, 0, 3, 0, 0, 3, 0, 1])
    assert _ep_escape(np.array([0, 0, 2], np.uint8)) == bytes([0, 0, 3, 2])
    assert _ep_escape(np.array([0, 0, 4], np.uint8)) == bytes([0, 0, 4])
    assert _ep_escape(np.array([1, 2, 3], np.uint8)) == bytes([1, 2, 3])
