"""EXECUTED web-client tests: the real web/*.js running under the
tools/minijs interpreter against browser stubs (tests/web_stubs.py).

This supersedes the regex contract checks in test_web_client.py for
logic coverage (VERDICT round-1 weakness 6 / item 9): demux, ACK
wraparound, decoder pools, input mapping, IME fallback, trackpad
scrolling, and the schema-driven dashboard all run for real here.
"""

import os
import struct
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from web_stubs import BrowserEnv, FakeWebSocket  # noqa: E402
from tools.minijs import (  # noqa: E402
    UNDEF, JSArray, JSObject, to_num, to_str)


# ------------------------------------------------------------ fixtures


def make_client(env, **opt_props):
    canvas = env.document.createElement("canvas")
    canvas.width, canvas.height = 1920.0, 1080.0
    props = {"canvas": canvas, "url": "ws://test/websockets"}
    props.update(opt_props)
    client = env.construct(env.exports["SelkiesClient"], [JSObject(props)])
    env.call(env.get(client, "connect"), [])
    ws = env.sockets[-1]
    ws.server_open()
    return client, ws, canvas


def jpeg_stripe(frame_id, y_start, payload=b"\xff\xd8flat\xff\xd9"):
    return bytes([3, 0]) + struct.pack(">HH", frame_id, y_start) + payload


@pytest.fixture(scope="module")
def client_env():
    return BrowserEnv(files=("selkies-client.js",))


@pytest.fixture()
def env(client_env):
    # fresh per-test state on a shared parsed environment
    client_env.sockets.clear()
    client_env.video_decoders.clear()
    client_env.audio_decoders.clear()
    client_env.bitmaps.clear()
    client_env.interp.timer_map.clear()
    client_env.document.listeners.clear()
    client_env.wake_locks.clear()
    return client_env


# ----------------------------------------------------------- handshake


def test_settings_handshake_and_server_push(env):
    client, ws, canvas = make_client(env)
    texts = ws.texts()
    assert texts and texts[0].startswith("SETTINGS,")
    assert '"encoder": "jpeg"' in texts[0]

    pushed = []
    obj = JSObject({})
    env.interp.globals.declare("__push", env.interp.py_to_js(None))

    # capture server_settings callback
    def on_settings(this, args, interp):
        pushed.append(args[0])
        return UNDEF
    from tools.minijs import NativeFunction
    env.interp.set_prop(client, "onServerSettings",
                        NativeFunction(on_settings))
    ws.server_text('{"type": "server_settings", '
                   '"settings": {"framerate": {"value": 60}}}')
    assert pushed and isinstance(pushed[0], JSObject)
    assert "framerate" in pushed[0].props


def test_viewer_mode_does_not_claim_display(env):
    client, ws, canvas = make_client(env, claimDisplay=False)
    assert not any(t.startswith("SETTINGS,") for t in ws.texts())


# --------------------------------------------------------------- demux


def test_jpeg_stripe_decodes_and_paints_at_y(env):
    client, ws, canvas = make_client(env)
    ws.server_binary(jpeg_stripe(7, 128))
    env.interp.run_microtasks()
    ctx = canvas.getContext("2d")
    assert ctx.draw_calls[-1][1:] == (0.0, 128.0)
    assert to_num(env.get(client, "lastFrameId")) == 7.0
    assert env.bitmaps[-1].closed          # bitmap released after paint


def test_stale_stripe_not_painted_over_newer(env):
    client, ws, canvas = make_client(env)
    ctx = canvas.getContext("2d")
    n0 = len(ctx.draw_calls)
    ws.server_binary(jpeg_stripe(100, 64))
    env.interp.run_microtasks()
    ws.server_binary(jpeg_stripe(99, 64))   # older frame for the same band
    env.interp.run_microtasks()
    assert len(ctx.draw_calls) == n0 + 1    # second stripe dropped
    ws.server_binary(jpeg_stripe(101, 64))
    env.interp.run_microtasks()
    assert len(ctx.draw_calls) == n0 + 2


def test_ack_only_advances_forward_with_wraparound(env):
    client, ws, canvas = make_client(env)
    ws.server_binary(jpeg_stripe(0xFFFE, 0))
    env.interp.run_microtasks()
    assert to_num(env.get(client, "lastFrameId")) == float(0xFFFE)
    # wraparound: 3 is "newer" than 0xFFFE mod 2^16
    ws.server_binary(jpeg_stripe(3, 64))
    env.interp.run_microtasks()
    assert to_num(env.get(client, "lastFrameId")) == 3.0
    # stale late stripe on another band must NOT regress the ACK id
    ws.server_binary(jpeg_stripe(0xFFFF, 128))
    env.interp.run_microtasks()
    assert to_num(env.get(client, "lastFrameId")) == 3.0
    # the ACK timer ships the held id
    env.interp.fire_timers(1)
    assert "CLIENT_FRAME_ACK 3" in ws.texts()


def test_full_frame_h264_waits_for_keyframe(env):
    client, ws, canvas = make_client(env)
    delta = bytes([0, 0]) + struct.pack(">H", 5) + b"\x00\x00\x00\x01\x41dd"
    ws.server_binary(delta)
    assert not env.video_decoders          # no decoder until a keyframe
    key = bytes([0, 1]) + struct.pack(">H", 6) + b"\x00\x00\x00\x01\x67kk"
    ws.server_binary(key)
    assert env.video_decoders
    dec = env.video_decoders[-1]
    assert [c.type for c in dec.chunks] == ["key"]
    assert dec.chunks[0].data == b"\x00\x00\x00\x01\x67kk"
    ws.server_binary(bytes([0, 0]) + struct.pack(">H", 7) + b"dd2")
    assert [c.type for c in dec.chunks] == ["key", "delta"]
    # decode error → decoders reset, next delta ignored until key
    dec.fail_next = True
    ws.server_binary(bytes([0, 0]) + struct.pack(">H", 8) + b"dd3")
    assert dec.state == "closed"
    assert env.get(client, "videoDecoder") is not dec


def test_striped_h264_per_stripe_decoder_pool(env):
    client, ws, canvas = make_client(env)

    def stripe(fid, y, key, payload):
        return bytes([4, 1 if key else 0]) + struct.pack(
            ">HH", fid, y) + b"\x00" * 4 + payload

    ws.server_binary(stripe(1, 0, True, b"s0"))
    ws.server_binary(stripe(1, 64, True, b"s1"))
    decs = env.get(client, "stripeDecoders")
    assert len(decs) == 2                  # one decoder per band
    # delta for an unknown band is ignored (no decoder without a key)
    ws.server_binary(stripe(2, 128, False, b"s2"))
    assert len(decs) == 2
    # decode error evicts that band's decoder only
    band0 = decs[0.0].props["dec"]
    band0.fail_next = True
    ws.server_binary(stripe(3, 0, False, b"s3"))
    assert len(decs) == 1


def test_audio_chunks_reach_worklet_ring(env):
    client, ws, canvas = make_client(env)
    ws.server_binary(bytes([1, 0]) + b"OPUSDATA")
    env.interp.run_microtasks()
    assert env.audio_decoders, "AudioDecoder never constructed"
    assert env.audio_decoders[-1].chunks[-1].data == b"OPUSDATA"
    assert env.worklet_nodes, "AudioWorklet ring not built"
    msg = env.worklet_nodes[-1].port.messages[-1]
    ch0 = msg.props["ch0"]
    assert ch0.length == 960               # one 20 ms frame landed


def test_pipeline_reset_clears_ack_and_decoders(env):
    client, ws, canvas = make_client(env)
    ws.server_binary(jpeg_stripe(50, 0))
    env.interp.run_microtasks()
    key = bytes([0, 1]) + struct.pack(">H", 51) + b"kf"
    ws.server_binary(key)
    dec = env.video_decoders[-1]
    ws.server_text("PIPELINE_RESETTING")
    assert to_num(env.get(client, "lastFrameId")) == -1.0
    assert dec.state == "closed"


def test_kill_supersedes_session(env):
    client, ws, canvas = make_client(env)
    statuses = []
    from tools.minijs import NativeFunction
    env.interp.set_prop(client, "onStatus", NativeFunction(
        lambda t, a, i: (statuses.append(to_str(a[0])), UNDEF)[1]))
    ws.server_text("KILL")
    assert "superseded" in statuses
    assert ws.readyState == FakeWebSocket.CLOSED


def test_clipboard_roundtrip_utf8(env):
    client, ws, canvas = make_client(env)
    got = []
    from tools.minijs import NativeFunction
    env.interp.set_prop(client, "onClipboard", NativeFunction(
        lambda t, a, i: (got.append(to_str(a[0])), UNDEF)[1]))
    import base64
    text = "héllo → wörld"
    ws.server_text("clipboard," +
                   base64.b64encode(text.encode("utf-8")).decode())
    assert got == [text]
    env.call(env.get(client, "sendClipboard"), [text])
    sent = [t for t in ws.texts() if t.startswith("cw,")][-1]
    assert base64.b64decode(sent[3:]).decode("utf-8") == text


def test_stream_resolution_resizes_canvas(env):
    client, ws, canvas = make_client(env)
    ws.server_text('{"type": "stream_resolution", '
                   '"width": 2560, "height": 1440}')
    assert canvas.width == 2560.0 and canvas.height == 1440.0


def test_stats_report_fps_accounting(env):
    client, ws, canvas = make_client(env)
    stats = []
    from tools.minijs import NativeFunction
    env.interp.set_prop(client, "onStats", NativeFunction(
        lambda t, a, i: (stats.append(a[0]), UNDEF)[1]))
    for fid in range(3):
        ws.server_binary(jpeg_stripe(fid, 0))
        env.interp.run_microtasks()
    env.interp.now_ms += 1000.0
    env.call(env.get(client, "_reportStats"), [], this=client)
    assert stats and to_str(stats[-1].props["type"]) == "client_stats"
    assert abs(to_num(stats[-1].props["fps"]) - 3.0) < 0.2
    assert any(t.startswith("_f ") for t in ws.texts())


# ----------------------------------------------------------- input.js


@pytest.fixture(scope="module")
def input_env():
    return BrowserEnv(files=("input.js",))


def make_input(ienv):
    from tools.minijs import NativeFunction
    sent = []
    client = JSObject({"send": NativeFunction(
        lambda t, a, i: (sent.append(to_str(a[0])), UNDEF)[1], "send")})
    el = ienv.document.createElement("canvas")
    el.width, el.height = 1920.0, 1080.0
    inp = ienv.construct(ienv.exports["SelkiesInput"], [client, el])
    ienv.call(ienv.get(inp, "attach"), [])
    return inp, el, sent


def key_ev(ienv, key, code="", **kw):
    return ienv.make_event("keydown", key=key, code=code,
                           keyCode=kw.pop("keyCode", 0), **kw)


def test_eventkeysym_mapping(input_env):
    ienv = input_env
    ks = ienv.exports["eventKeysym"]
    assert to_num(ienv.call(ks, [key_ev(ienv, "a")])) == 97.0
    assert to_num(ienv.call(ks, [key_ev(ienv, "é")])) == 233.0  # latin-1
    # X11 unicode rule above latin-1
    assert to_num(ienv.call(ks, [key_ev(ienv, "あ")])) == 0x01000000 + 0x3042
    assert to_num(ienv.call(ks, [key_ev(ienv, "Enter")])) == 0xFF0D
    # ev.code beats ev.key for keypad distinction
    assert to_num(ienv.call(ks, [key_ev(ienv, "7", "Numpad7")])) == 0xFFB7
    assert ienv.call(ks, [key_ev(ienv, "SomeUnknownKey")]) is None


def test_keydown_sends_keysym_and_window_blur_releases(input_env):
    ienv = input_env
    inp, el, sent = make_input(ienv)
    for fn in ienv.window.listeners["keydown"]:
        ienv.call(fn, [key_ev(ienv, "a")])
    assert sent[-1] == "kd,97"
    for fn in ienv.window.listeners["keyup"]:
        ienv.call(fn, [ienv.make_event("keyup", key="a", code="",
                                       keyCode=0)])
    assert sent[-1] == "ku,97"
    for fn in ienv.window.listeners["blur"]:
        ienv.call(fn, [ienv.make_event("blur")])
    assert sent[-1] == "kr"


def test_composition_end_sends_atomic_text(input_env):
    ienv = input_env
    inp, el, sent = make_input(ienv)
    proxy = ienv.get(inp, "_imeProxy")
    ienv.fire(proxy, "compositionstart", ienv.make_event(
        "compositionstart"))
    # keydown during composition must NOT emit keysyms
    n0 = len(sent)
    for fn in ienv.window.listeners["keydown"]:
        ienv.call(fn, [ienv.make_event("keydown", key="Process",
                                       keyCode=229, isComposing=True)])
    assert len(sent) == n0
    ienv.fire(proxy, "compositionend", ienv.make_event(
        "compositionend", data="日本語"))
    assert sent[-1] == "co,end,日本語"


def test_osk_char_after_enter_not_swallowed(input_env):
    """Regression: a preventDefault'ed Enter used to latch _sentKey and
    swallow the next on-screen-keyboard character."""
    ienv = input_env
    inp, el, sent = make_input(ienv)
    proxy = ienv.get(inp, "_imeProxy")
    # OSK Enter: a real key event, handled
    for fn in ienv.window.listeners["keydown"]:
        ienv.call(fn, [key_ev(ienv, "Enter")])
    assert sent[-1] == "kd,65293"
    # OSK 'a': keydown is Unidentified (ignored), text arrives via input
    for fn in ienv.window.listeners["keydown"]:
        ienv.call(fn, [key_ev(ienv, "Unidentified")])
    ienv.fire(proxy, "input", ienv.make_event(
        "input", inputType="insertText", data="a"))
    assert sent[-1] == "co,end,a", "first OSK char after Enter swallowed"


def test_mouse_move_and_buttons(input_env):
    ienv = input_env
    inp, el, sent = make_input(ienv)
    ienv.fire(el, "mousedown", ienv.make_event(
        "mousedown", button=0.0, clientX=10.0, clientY=20.0))
    assert sent[-1].startswith("m,") and ",1,0" in sent[-1]
    ienv.fire(el, "mouseup", ienv.make_event(
        "mouseup", button=0.0, clientX=10.0, clientY=20.0))
    assert ",0,0" in sent[-1]


def test_wheel_scroll_bits(input_env):
    ienv = input_env
    inp, el, sent = make_input(ienv)
    ienv.fire(el, "wheel", ienv.make_event(
        "wheel", deltaY=-120.0, clientX=0.0, clientY=0.0))
    assert ",8," in sent[-1]     # scroll-up bit
    ienv.fire(el, "wheel", ienv.make_event(
        "wheel", deltaY=120.0, clientX=0.0, clientY=0.0))
    assert ",16," in sent[-1]    # scroll-down bit


def touch_ev(ienv, type_, touches, changed=None):
    mk = lambda pts: JSArray([JSObject({
        "clientX": float(x), "clientY": float(y)}) for x, y in pts])
    return ienv.make_event(type_, touches=mk(touches),
                           changedTouches=mk(changed or touches))


def test_trackpad_two_finger_scroll_sends_press_release_pairs(input_env):
    """Regression: a held scroll bit latched server-side after one notch;
    each notch must be a press/release pair."""
    ienv = input_env
    inp, el, sent = make_input(ienv)
    ienv.call(ienv.get(inp, "toggleTrackpadMode"), [], this=inp)
    ienv.fire(el, "touchstart", touch_ev(
        ienv, "touchstart", [(100, 100), (120, 100)]))
    n0 = len(sent)
    ienv.fire(el, "touchmove", touch_ev(
        ienv, "touchmove", [(100, 145), (120, 145)]))   # 45px → 2 notches
    new = sent[n0:]
    assert new == ["m2,0,0,8,1", "m2,0,0,0,0",
                   "m2,0,0,8,1", "m2,0,0,0,0"]
    ienv.call(ienv.get(inp, "toggleTrackpadMode"), [], this=inp)


def test_trackpad_tap_clicks_and_two_finger_tap_right_clicks(input_env):
    ienv = input_env
    inp, el, sent = make_input(ienv)
    ienv.call(ienv.get(inp, "toggleTrackpadMode"), [], this=inp)
    # single tap
    ienv.fire(el, "touchstart", touch_ev(ienv, "touchstart", [(50, 50)]))
    ienv.fire(el, "touchend", touch_ev(ienv, "touchend", [], [(50, 50)]))
    assert sent[-2:] == ["m2,0,0,1,0", "m2,0,0,0,0"]
    # two-finger tap → right click
    ienv.fire(el, "touchstart", touch_ev(
        ienv, "touchstart", [(50, 50), (70, 50)]))
    ienv.fire(el, "touchend", touch_ev(ienv, "touchend", [], [(50, 50)]))
    assert sent[-2:] == ["m2,0,0,4,0", "m2,0,0,0,0"]
    ienv.call(ienv.get(inp, "toggleTrackpadMode"), [], this=inp)


def test_gamepad_connect_and_poll(input_env):
    ienv = input_env
    inp, el, sent = make_input(ienv)
    from tools.minijs import NativeFunction
    pad = JSObject({
        "index": 0.0, "id": "X360 pad",
        "axes": JSArray([0.0, 0.0]),
        "buttons": JSArray([JSObject({"value": 0.0}),
                            JSObject({"value": 0.0})]),
    })
    ienv.gamepads = JSArray([pad])
    for fn in ienv.window.listeners["gamepadconnected"]:
        ienv.call(fn, [JSObject({"gamepad": pad})])
    assert any(t.startswith("js,c,0,") and t.endswith(",2,2")
               for t in sent)
    # press a button and move an axis, then poll
    pad.props["buttons"].elems[1].props["value"] = 1.0
    pad.props["axes"].elems[0] = 0.5
    ienv.call(ienv.get(inp, "_pollGamepads"), [], this=inp)
    assert "js,b,0,1,1.000" in sent
    assert "js,a,0,0,0.500" in sent


# -------------------------------------------------------- dashboard.js


@pytest.fixture(scope="module")
def dash_env():
    return BrowserEnv(files=("selkies-client.js", "input.js",
                             "touch-gamepad.js", "dashboard.js"))


SCHEMA = ('{"type": "server_settings", "settings": {'
          '"encoder": {"value": "jpeg", "allowed": ["jpeg", "x264enc"]},'
          '"framerate": {"value": 60, "min": 8, "max": 120},'
          '"jpeg_quality": {"value": 40, "min": 1, "max": 100},'
          '"audio_enabled": {"value": true},'
          '"use_cpu": {"value": false, "locked": true},'
          '"ui_title": {"value": "My Desk"},'
          '"file_transfers": {"value": ["upload", "download"]},'
          '"clipboard_enabled": {"value": true},'
          '"gamepad_enabled": {"value": true},'
          '"custom_knob": {"value": 3, "min": 0, "max": 9}'
          "}}")


def make_dashboard(denv, mode="full"):
    denv.sockets.clear()
    denv.local_storage.clear()
    root = denv.document.createElement("div")
    canvas = denv.document.createElement("canvas")
    canvas.width, canvas.height = 1920.0, 1080.0
    dash = denv.construct(denv.exports["SelkiesDashboard"], [JSObject({
        "root": root, "canvas": canvas, "wsUrl": "ws://t/ws",
        "mode": mode})])
    # click Connect
    btns = root.find_all(lambda e: e.tagName == "BUTTON"
                         and e.textContent == "Connect")
    denv.fire(btns[0], "click")
    ws = denv.sockets[-1]
    ws.server_open()
    return dash, root, canvas, ws


def test_dashboard_renders_schema_sections(dash_env):
    dash, root, canvas, ws = make_dashboard(dash_env)
    ws.server_text(SCHEMA)
    widgets = dash_env.get(dash, "widgets")
    assert "framerate" in widgets and "encoder" in widgets
    assert "custom_knob" in widgets           # unknown → Advanced section
    # locked bool renders disabled
    assert widgets["use_cpu"].disabled is True
    # enum select carries the allowed values as options
    enc = widgets["encoder"]
    opts = [to_str(o.attrs.get("value")) for o in enc.children.elems]
    assert opts == ["jpeg", "x264enc"]
    # ui_title applied
    assert dash_env.get(dash, "titleEl").textContent == "My Desk"


def test_dashboard_checkbox_pushes_clamped_settings(dash_env):
    dash, root, canvas, ws = make_dashboard(dash_env)
    ws.server_text(SCHEMA)
    widgets = dash_env.get(dash, "widgets")
    box = widgets["audio_enabled"]
    box.checked = False
    dash_env.fire(box, "change", dash_env.make_event(
        "change", target=box))
    # START/STOP_AUDIO immediate + debounced SETTINGS push
    assert "STOP_AUDIO" in ws.texts()
    dash_env.interp.fire_timers(1)
    pushes = [t for t in ws.texts() if t.startswith("SETTINGS,")]
    assert '"audio_enabled": false' in pushes[-1]
    # override persisted to localStorage
    assert '"audio_enabled": false' in \
        dash_env.local_storage["selkies_settings"]


def test_dashboard_number_input_clamps_to_schema_range(dash_env):
    dash, root, canvas, ws = make_dashboard(dash_env)
    ws.server_text(SCHEMA)
    widgets = dash_env.get(dash, "widgets")
    fr = widgets["framerate"]
    fr.value = "500"                           # out of range
    dash_env.fire(fr, "change", dash_env.make_event("change", target=fr))
    assert to_num(fr.value) == 120.0           # clamped to schema max
    dash_env.interp.fire_timers(1)
    pushes = [t for t in ws.texts() if t.startswith("SETTINGS,")]
    assert '"framerate": 120' in pushes[-1]


def test_dashboard_stats_render(dash_env):
    dash, root, canvas, ws = make_dashboard(dash_env)
    ws.server_text(SCHEMA)
    ws.server_text('{"type": "system_stats", "cpu_percent": 31,'
                   ' "mem_percent": 40}')
    ws.server_text('{"type": "gpu_stats", "utilization": 77}')
    stats_el = dash_env.get(dash, "statsEl")
    assert "31%" in stats_el.textContent
    assert "77%" in stats_el.textContent


def test_dashboard_stage_breakdown_render(dash_env):
    # ISSUE 13: the flight-recorder stage block riding system_health is
    # rendered in the stats overlay (where each frame's time went)
    dash, root, canvas, ws = make_dashboard(dash_env)
    ws.server_text(SCHEMA)
    ws.server_text(
        '{"type": "system_health", "displays": {"primary": {'
        '"rung": "device", "glass_to_glass_p50_ms": 42.5,'
        ' "stages": {"capture": {"p50_ms": 1.3, "p95_ms": 3.0},'
        ' "ack": {"p50_ms": 12.0, "p95_ms": 30.0}}}}}')
    stats_el = dash_env.get(dash, "statsEl")
    assert "g2g 42.5 ms" in stats_el.textContent
    assert "capture 1.3" in stats_el.textContent
    assert "ack 12.0" in stats_el.textContent


def test_dashboard_sharing_links_and_copy(dash_env):
    dash, root, canvas, ws = make_dashboard(dash_env)
    dash_env.clipboard_writes.clear()
    ws.server_text(SCHEMA)
    host = dash_env.get(dash, "settingsHost")
    rows = host.find_all(lambda e: "share-row" in (e.className or ""))
    labels = [r.children.elems[0].textContent for r in rows]
    assert labels == ["View only", "Player 2", "Player 3", "Player 4"]
    copy_btn = rows[1].children.elems[1]
    dash_env.fire(copy_btn, "click", dash_env.make_event(
        "click", target=copy_btn))
    assert dash_env.clipboard_writes[-1].endswith("#player2")


def test_dashboard_files_modal_toggle(dash_env):
    dash, root, canvas, ws = make_dashboard(dash_env)
    ws.server_text(SCHEMA)
    host = dash_env.get(dash, "settingsHost")
    dl = host.find_all(lambda e: e.tagName == "BUTTON"
                       and e.textContent == "Download files")
    assert dl, "download button missing though file_transfers allows it"
    dash_env.fire(dl[0], "click")
    modal = dash_env.get(dash, "_filesModal")
    assert modal is not None and modal is not UNDEF
    iframes = modal.find_all(lambda e: e.tagName == "IFRAME")
    assert iframes and iframes[0].attrs.get("src") == "./files/"
    dash_env.fire(dl[0], "click")              # toggle off
    assert dash_env.get(dash, "_filesModal") is None


def test_dashboard_player_mode_is_gamepad_only(dash_env):
    dash, root, canvas, ws = make_dashboard(dash_env, mode="player2")
    # gamepad-only client never claims the display
    assert not any(t.startswith("SETTINGS,") for t in ws.texts())
    inp = dash_env.get(dash, "input")
    assert to_num(dash_env.get(inp, "gamepadIndexOffset")) == 1.0


# ----------------------------------------------------- touch-gamepad.js


def test_touch_gamepad_patches_getgamepads(dash_env):
    denv = dash_env
    tg = denv.interp.globals.lookup("TouchGamepad")
    denv.call(denv.get(tg, "enable"), [])
    pads = denv.call(denv.interp.globals.lookup("navigator").props[
        "getGamepads"], [])
    virt = pads.elems[3]
    assert virt is not None and virt is not UNDEF
    assert "Touch Gamepad" in to_str(denv.get(virt, "id"))
    # stick touch drives axes on the virtual pad
    overlay = denv.document.body.children.elems[-1]
    w, h = 1920.0, 1080.0
    ev = denv.make_event(
        "touchstart",
        changedTouches=JSArray([JSObject({
            "identifier": 1.0,
            "clientX": 0.18 * w + 50.0, "clientY": 0.72 * h})]))
    denv.fire(overlay, "touchstart", ev)
    axes = denv.get(virt, "axes")
    assert to_num(axes.elems[0]) > 0.3         # pushed right
    denv.call(denv.get(tg, "disable"), [])
    pads2 = denv.call(denv.interp.globals.lookup("navigator").props[
        "getGamepads"], [])
    assert pads2 is denv.gamepads              # native restored


def test_wake_lock_lifecycle(env):
    client, ws, canvas = make_client(env)
    env.interp.run_microtasks()
    assert env.wake_locks, "wake lock not requested on connect"
    lock = env.wake_locks[-1]
    # tab hidden → UA releases; on return to foreground, re-acquire
    env.document.visibilityState = "visible"
    n0 = len(env.wake_locks)
    for fn in env.document.listeners.get("visibilitychange", []):
        env.call(fn, [env.make_event("visibilitychange")])
    assert len(env.wake_locks) == n0 + 1
    env.call(env.get(client, "disconnect"), [])
    assert env.wake_locks[-1].props["released"] is True


def test_upload_file_chunks_and_frames(env):
    """uploadFile: START/chunk/END protocol with 0x01-framed binary and
    the bufferedAmount backpressure loop."""
    client, ws, canvas = make_client(env)
    from web_stubs import FakeBlobSlice
    from tools.minijs import NativeFunction

    data = bytes(range(256)) * 1200          # 300 KB → 2 chunks @ 256 KB

    class FakeFile:
        name = "report.pdf"
        size = float(len(data))

        def slice(self, a, b):
            return FakeBlobSlice(env, data[int(to_num(a)):int(to_num(b))])

    env.call(env.get(client, "uploadFile"), [FakeFile()], this=client)
    texts = ws.texts()
    assert f"FILE_UPLOAD_START:report.pdf:{len(data)}" in texts
    assert "FILE_UPLOAD_END:report.pdf" in texts
    bins = [b for b in ws.sent if isinstance(b, bytes) and b[:1] == b"\x01"]
    assert len(bins) == 2                    # 256 KB + 44 KB
    assert b"".join(b[1:] for b in bins) == data
