import numpy as np
import pytest

from selkies_tpu.encoder import entropy_py
from selkies_tpu.encoder.device_entropy import (
    DeviceEntropyPacker,
    stuff_bytes,
    words_to_stripe_bytes,
)


def random_coeffs(rng, by, bx, density=0.15, amp=400):
    """Sparse int16 zigzag coefficients within legal category ranges."""
    c = (rng.integers(-amp, amp + 1, size=(by, bx, 64))
         * (rng.random((by, bx, 64)) < density)).astype(np.int16)
    return c


def host_reference(yq, cbq, crq, stripe_h):
    yrows, crows = stripe_h // 8, stripe_h // 16
    s_cnt = yq.shape[0] // yrows
    return [
        entropy_py.encode_scan_420(
            yq[s * yrows:(s + 1) * yrows],
            cbq[s * crows:(s + 1) * crows],
            crq[s * crows:(s + 1) * crows],
        )
        for s in range(s_cnt)
    ]


@pytest.mark.parametrize("pad_h,pad_w,stripe_h,density", [
    (64, 64, 64, 0.15),
    (128, 96, 64, 0.3),
    (192, 128, 64, 0.02),
    (64, 32, 32, 0.6),
])
def test_device_pack_matches_host_oracle(pad_h, pad_w, stripe_h, density):
    rng = np.random.default_rng(pad_h * 1000 + pad_w)
    by, bx = pad_h // 8, pad_w // 8
    cby, cbx = pad_h // 16, pad_w // 16
    yq = random_coeffs(rng, by, bx, density)
    cbq = random_coeffs(rng, cby, cbx, density / 2, amp=200)
    crq = random_coeffs(rng, cby, cbx, density / 2, amp=200)

    packer = DeviceEntropyPacker(pad_h, pad_w, stripe_h)
    words, nbytes, base_words, overflow = packer.pack(yq, cbq, crq)
    assert not np.asarray(overflow).any()
    stripes = words_to_stripe_bytes(
        np.asarray(words), np.asarray(base_words), np.asarray(nbytes))

    ref = host_reference(yq, cbq, crq, stripe_h)
    assert len(stripes) == len(ref)
    for s, (dev, host) in enumerate(zip(stripes, ref)):
        assert stuff_bytes(dev) == host, f"stripe {s} mismatch"


def test_device_pack_extreme_values():
    """DC swings near the category-11 limit and dense max-amp ACs."""
    pad_h = pad_w = 64
    by = bx = 8
    rng = np.random.default_rng(3)
    yq = random_coeffs(rng, by, bx, 0.9, amp=800)
    yq[:, :, 0] = rng.integers(-1000, 1000, size=(by, bx))  # wild DC deltas
    cbq = random_coeffs(rng, 4, 4, 0.9, amp=800)
    crq = random_coeffs(rng, 4, 4, 0.9, amp=800)
    packer = DeviceEntropyPacker(pad_h, pad_w, 64)
    words, nbytes, base_words, overflow = packer.pack(yq, cbq, crq)
    assert not np.asarray(overflow).any()
    dev = words_to_stripe_bytes(
        np.asarray(words), np.asarray(base_words), np.asarray(nbytes))[0]
    assert stuff_bytes(dev) == host_reference(yq, cbq, crq, 64)[0]


def test_all_zero_blocks():
    packer = DeviceEntropyPacker(64, 64, 64)
    z = np.zeros((8, 8, 64), np.int16)
    zc = np.zeros((4, 4, 64), np.int16)
    words, nbytes, base_words, overflow = packer.pack(z, zc, zc)
    dev = words_to_stripe_bytes(
        np.asarray(words), np.asarray(base_words), np.asarray(nbytes))[0]
    assert stuff_bytes(dev) == host_reference(z, zc, zc, 64)[0]


def test_stuff_bytes():
    assert stuff_bytes(b"\xff\x00\xff") == b"\xff\x00\x00\xff\x00"
    assert stuff_bytes(b"abc") == b"abc"


def test_no_default_precision_f32_matmuls_in_pack_graph():
    """MXU-precision canary: the TPU lowers DEFAULT-precision f32
    dot_generals to bf16 operand rounding, which silently corrupts the
    packed Huffman table (found on a real v5e: stripes decoded at ~10 dB
    while every CPU test passed). CPU runs can't reproduce that rounding,
    so instead assert structurally that every floating dot in the pack
    graph pins Precision.HIGHEST."""
    import jax
    import jax.numpy as jnp

    packer = DeviceEntropyPacker(32, 32, 32)
    yq = jnp.zeros((4, 4, 64), jnp.int16)
    cq = jnp.zeros((2, 2, 64), jnp.int16)
    jaxpr = jax.make_jaxpr(packer._pack_fn)(yq, cq, cq)

    def walk(jx, out):
        for eqn in jx.eqns:
            if eqn.primitive.name == "dot_general":
                out.append(eqn)
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    walk(v.jaxpr, out)
        return out

    dots = walk(jaxpr.jaxpr, [])
    assert dots, "expected at least the _lut512 one-hot matmul"
    for eqn in dots:
        if any(jnp.issubdtype(v.aval.dtype, jnp.floating)
               for v in eqn.invars):
            prec = eqn.params.get("precision")
            assert prec is not None and "HIGHEST" in str(prec), (
                f"f32 dot_general with default precision in pack graph: "
                f"{eqn.params}")
