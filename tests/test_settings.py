from selkies_tpu.settings import (
    BoolValue,
    RangeValue,
    SETTING_DEFINITIONS,
    Settings,
)


def mk(argv=(), env=None):
    return Settings(argv=list(argv), env=env or {})


def test_defaults():
    s = mk()
    assert s.port == 8082
    assert s.encoder == "jpeg"
    assert s.framerate == RangeValue(8, 120, 60)
    assert s.audio_enabled.value is True
    assert s.file_transfers == ("upload", "download")


def test_precedence_cli_over_env():
    s = mk(argv=["--port", "9000"], env={"SELKIES_PORT": "7000"})
    assert s.port == 9000


def test_env_over_legacy_env():
    s = mk(env={"SELKIES_PORT": "7000", "CUSTOM_WS_PORT": "6000"})
    assert s.port == 7000
    s2 = mk(env={"CUSTOM_WS_PORT": "6000"})
    assert s2.port == 6000


def test_bool_locked_suffix():
    s = mk(env={"SELKIES_USE_CPU": "true|locked"})
    assert s.use_cpu == BoolValue(True, locked=True)


def test_range_single_value_locks():
    s = mk(env={"SELKIES_FRAMERATE": "60"})
    assert s.framerate.locked
    assert s.framerate.clamp(200) == 60


def test_range_parse_and_clamp():
    s = mk(env={"SELKIES_JPEG_QUALITY": "10-80"})
    q = s.jpeg_quality
    assert (q.lo, q.hi) == (10, 80)
    assert q.clamp(100) == 80
    assert q.clamp(1) == 10


def test_list_none_disables():
    s = mk(env={"SELKIES_FILE_TRANSFERS": "none"})
    assert s.file_transfers == ()


def test_schema_payload_shape():
    payload = mk().schema_payload()
    assert payload["type"] == "server_settings"
    st = payload["settings"]
    # server-only settings excluded, like the reference handshake
    assert "port" not in st and "debug" not in st
    assert st["audio_enabled"] == {"value": True, "locked": False}
    fr = st["framerate"]
    assert (fr["min"], fr["max"], fr["default"]) == (8, 120, 60)
    assert "allowed" in st["encoder"]


def test_clamp_client_value():
    s = mk(env={"SELKIES_USE_CPU": "false|locked"})
    assert s.clamp_client_value("use_cpu", True) is False
    assert s.clamp_client_value("jpeg_quality", 500) == 100
    assert s.clamp_client_value("encoder", "nvh264enc") == "jpeg"
    assert s.clamp_client_value("encoder", "x264enc-striped") == "x264enc-striped"


def test_every_spec_has_help_and_unique_name():
    names = [sp.name for sp in SETTING_DEFINITIONS]
    assert len(names) == len(set(names))
    assert all(sp.help for sp in SETTING_DEFINITIONS)


def test_clamp_bool_accepts_numeric_strings():
    s = mk()
    assert s.clamp_client_value("audio_enabled", "1") is True
    assert s.clamp_client_value("audio_enabled", "0") is False


def test_schema_range_value_is_json_safe():
    import json
    json.dumps(mk().schema_payload())


def test_enum_comma_list_restricts_allowed():
    """Reference override semantics (reference settings.py:29-31): a comma
    list restricts the allowed options and its first item is the default."""
    s = mk(env={"SELKIES_ENCODER": "jpeg,x264enc"})
    assert s.encoder == "jpeg"
    entry = s.schema_payload()["settings"]["encoder"]
    assert entry["value"] == "jpeg"
    assert entry["allowed"] == ["jpeg", "x264enc"]
    # clamp honors the restriction, not the spec-wide list
    assert s.clamp_client_value("encoder", "x264enc-striped") == "jpeg"
    assert s.clamp_client_value("encoder", "x264enc") == "x264enc"


def test_enum_single_value_locks_choice():
    s = mk(env={"SELKIES_ENCODER": "jpeg"})
    assert s.encoder == "jpeg"
    assert s.encoder.locked
    entry = s.schema_payload()["settings"]["encoder"]
    assert entry["allowed"] == ["jpeg"]
    assert s.clamp_client_value("encoder", "x264enc") == "jpeg"


def test_enum_default_keeps_full_allowed():
    s = mk()
    entry = s.schema_payload()["settings"]["encoder"]
    assert entry["allowed"] == ["x264enc", "x264enc-striped", "jpeg"]
    assert not s.encoder.locked


def test_enum_rejects_unknown_in_list():
    import pytest
    with pytest.raises(ValueError):
        mk(env={"SELKIES_ENCODER": "jpeg,notreal"})
