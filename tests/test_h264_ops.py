"""H.264 transform/quant/motion op tests against independent numpy mirrors."""

import numpy as np
import pytest

from selkies_tpu.ops import h264_transform as ht
from selkies_tpu.ops.motion import (NumpyMotionMirror, full_search_mv,
                                    mc_chroma, mc_luma)

RNG = np.random.default_rng(42)


# ---------------------------------------------------------------------------
# transforms


def test_forward_inverse_roundtrip_lossless_at_qp0():
    """At QP 0 (and low magnitudes) quant→dequant→idct must invert the
    forward path to within the known H.264 reconstruction envelope."""
    x = RNG.integers(-255, 256, (64, 4, 4)).astype(np.int32)
    w = np.asarray(ht.forward_dct4(x))
    for qp in (0, 10, 24, 38, 51):
        z = np.asarray(ht.quant4(w, qp, intra=True))
        d = np.asarray(ht.dequant4(z, qp))
        r = np.asarray(ht.inverse_dct4(d))
        qstep = 0.625 * 2 ** (qp / 6)
        # measured envelope ≈1.3-1.6×Qstep (intra deadzone + basis norms)
        assert np.abs(r - x).max() <= qstep * 2 + 2, qp


def test_inverse_dct_matches_numpy_mirror():
    d = RNG.integers(-2000, 2000, (128, 4, 4)).astype(np.int32)
    ours = np.asarray(ht.inverse_dct4(d))
    mirror = ht.NumpyMirror.inverse_dct4(d)
    np.testing.assert_array_equal(ours, mirror)


def test_dequant_matches_mirror():
    z = RNG.integers(-100, 100, (32, 4, 4)).astype(np.int32)
    for qp in (0, 7, 23, 36, 51):
        np.testing.assert_array_equal(
            np.asarray(ht.dequant4(z, qp)), ht.NumpyMirror.dequant4(z, qp))
        np.testing.assert_array_equal(
            np.asarray(ht.dequant_dc16(z, qp)),
            ht.NumpyMirror.dequant_dc16(z, qp))
    for qpc in (0, 17, 29, 39):
        z2 = RNG.integers(-100, 100, (32, 2, 2)).astype(np.int32)
        np.testing.assert_array_equal(
            np.asarray(ht.dequant_dc2(z2, qpc)),
            ht.NumpyMirror.dequant_dc2(z2, qpc))


def test_dc16_roundtrip():
    """I16 DC path: decoder output must land in the AC dequant domain,
    i.e. rec ≈ 4·dc (d = 4·W consistency), within a Qstep-scaled bound."""
    dc = RNG.integers(-4080, 4080, (16, 4, 4)).astype(np.int32)
    for qp in (0, 20, 36, 44):
        y = np.asarray(ht.hadamard4_fwd(dc))
        z = np.asarray(ht.quant_dc16(y, qp))
        rec = np.asarray(ht.dequant_dc16(z, qp))
        qstep = 0.625 * 2 ** (qp / 6)
        err = np.abs(rec / 4.0 - dc)
        # inverse Hadamard spreads per-level error ×4 (in units of 4·W)
        assert err.max() <= qstep * 4 + 4, (qp, err.max())


def test_dc2_roundtrip():
    dc = RNG.integers(-4080, 4080, (16, 2, 2)).astype(np.int32)
    for qp in (0, 20, 39):
        y = np.asarray(ht.hadamard2_fwd(dc))
        z = np.asarray(ht.quant_dc2(y, qp))
        rec = np.asarray(ht.dequant_dc2(z, qp))
        qstep = 0.625 * 2 ** (qp / 6)
        err = np.abs(rec / 4.0 - dc)
        assert err.max() <= qstep * 4 + 4, (qp, err.max())


def test_qpc_table():
    assert ht.qpc_for(20) == 20
    assert ht.qpc_for(30) == 29
    assert ht.qpc_for(40) == 36
    assert ht.qpc_for(51) == 39


def test_block_layout_roundtrip():
    import jax.numpy as jnp
    p = jnp.asarray(RNG.integers(0, 255, (16, 32)))
    b = ht.plane_to_blocks(p)
    assert b.shape == (4, 8, 4, 4)
    np.testing.assert_array_equal(np.asarray(ht.blocks_to_plane(b)),
                                  np.asarray(p))


# ---------------------------------------------------------------------------
# motion


def test_full_search_finds_translation():
    h, w = 64, 128
    ref = RNG.integers(0, 256, (h, w)).astype(np.uint8)
    # shift content by (3, -5): cur[y, x] = ref[y-3, x+5]
    cur = np.roll(np.roll(ref, 3, axis=0), -5, axis=1)
    mv, sad0, best = full_search_mv(cur, ref, search=8)
    mv = np.asarray(mv)
    # interior MBs must find exactly (-3, +5)... mv points from cur into ref
    inner = mv[1:-1, 1:-1]
    assert (inner[..., 0] == -3).all() and (inner[..., 1] == 5).all()
    assert np.asarray(best)[1:-1, 1:-1].max() == 0


def test_full_search_zero_bias_on_flat():
    flat = np.full((32, 32), 77, np.uint8)
    mv, sad0, best = full_search_mv(flat, flat, search=4)
    assert (np.asarray(mv) == 0).all()   # ties must resolve to (0,0)


def test_mc_luma_matches_mirror():
    h, w = 32, 48
    ref = RNG.integers(0, 256, (h, w)).astype(np.uint8)
    mv = RNG.integers(-6, 7, (h // 16, w // 16, 2)).astype(np.int32)
    ours = np.asarray(mc_luma(ref, mv, search=8))
    mirror = NumpyMotionMirror.mc_luma(ref, mv)
    np.testing.assert_array_equal(ours, mirror)


def test_mc_luma_edge_extension():
    """MVs pointing outside the plane must clamp like the decoder."""
    ref = np.arange(32 * 32, dtype=np.uint8).reshape(32, 32)
    mv = np.full((2, 2, 2), -8, np.int32)   # everything points up-left
    ours = np.asarray(mc_luma(ref, mv, search=8))
    mirror = NumpyMotionMirror.mc_luma(ref, mv)
    np.testing.assert_array_equal(ours, mirror)


def test_mc_chroma_halfpel_matches_mirror():
    hc, wc = 16, 24
    ref_c = RNG.integers(0, 256, (hc, wc)).astype(np.uint8)
    # odd MVs exercise the half-pel bilinear path
    mv = RNG.integers(-5, 6, (hc // 8, wc // 8, 2)).astype(np.int32)
    ours = np.asarray(mc_chroma(ref_c, mv, search=8))
    mirror = NumpyMotionMirror.mc_chroma(ref_c, mv)
    np.testing.assert_array_equal(ours, mirror)


def test_batched_search_over_stripes():
    stripes = RNG.integers(0, 256, (3, 32, 64)).astype(np.uint8)
    mv, sad0, best = full_search_mv(stripes, stripes, search=4)
    assert np.asarray(mv).shape == (3, 2, 4, 2)
    assert (np.asarray(best) == 0).all()


def test_pipelined_h264_matches_synchronous():
    """PipelinedH264Encoder (grouped sparse fetches) must produce the
    byte-identical stream the synchronous encoder does."""
    import numpy as np
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedH264Encoder

    rng = np.random.default_rng(5)

    def frame(t, h=96, w=160):
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
        base = 128 + 80 * np.sin((xx + 7 * t) / 23) * np.cos(yy / 17)
        f = np.clip(np.stack([base, base + 10, base - 10], -1),
                    0, 255).astype(np.uint8)
        return f

    a = H264StripeEncoder(160, 96, stripe_height=32, qp=24)
    b = H264StripeEncoder(160, 96, stripe_height=32, qp=24)
    pipe = PipelinedH264Encoder(b, depth=6, fetch_group=3)

    want = []
    for t in range(8):
        want.append([(s.y_start, s.is_key, s.annexb)
                     for s in a.encode_frame(frame(t))])
    got_frames = {}
    for t in range(8):
        pipe.submit(frame(t))
        for seq, stripes in pipe.poll():
            got_frames[seq] = stripes
    for seq, stripes in pipe.flush():
        got_frames[seq] = stripes
    assert len(got_frames) == 8
    for t in range(8):
        got = [(s.y_start, s.is_key, s.annexb) for s in got_frames[t]]
        assert got == want[t], f"frame {t} diverged"


def test_sparse_pack_roundtrip_exact():
    """Device sparse pack vs the dense flat16 it summarizes."""
    import jax.numpy as jnp
    import numpy as np
    from selkies_tpu.encoder import h264_device as dev

    rng = np.random.default_rng(9)
    S, W = 3, 5000
    flat = np.zeros((S, W), np.int16)
    # sparse content + one dense stripe + one |level|>127 stripe
    for i in range(40):
        flat[0, rng.integers(0, W)] = rng.integers(-100, 100)
    flat[1, :] = rng.integers(-5, 5, W)            # count overflow
    flat[2, 100] = 300                             # range overflow
    damage = jnp.asarray([True, True, True])
    buf = np.asarray(dev._pack_sparse(
        jnp.asarray(flat), damage, damage, cap_frac=4))
    pad_words, n_cells, cap = dev.sparse_geometry(W)
    head = buf[:4 * S].reshape(S, 4)
    counts = head[:, 0].astype(int) + (head[:, 1].astype(int) << 8)
    ovf = head[:, 3] != 0
    assert not ovf[0] and ovf[1] and ovf[2]
    fixed = 4 * S + S * (n_cells // 8)
    bitmaps = buf[4 * S:fixed].reshape(S, n_cells // 8)
    used = np.minimum(counts, cap) * dev.CELL
    starts = np.concatenate([[0], np.cumsum(used)[:-1]]) + fixed
    bits = np.unpackbits(bitmaps[0], bitorder="little")[:n_cells]
    idx = np.flatnonzero(bits)
    cells = buf[starts[0]:starts[0] + used[0]].view(np.int8) \
        .astype(np.int32).reshape(-1, dev.CELL)
    dense = np.zeros(pad_words, np.int32)
    dense.reshape(-1, dev.CELL)[idx[:len(cells)]] = cells
    np.testing.assert_array_equal(dense[:W], flat[0])
